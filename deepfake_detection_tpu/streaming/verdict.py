"""Per-stream score aggregation: EMA + hysteresis verdict state machine.

A live stream produces a noisy sequence of per-window fake scores; the
product question is a *stable* per-stream answer ("is this feed fake?")
that neither flaps on score noise nor lags a real manipulation.  The
classic control answer is used here:

* an **EMA** over window scores absorbs single-window noise (one bad crop
  or a shed window cannot flip the verdict);
* **hysteresis** thresholds make every state change sticky — each state is
  *entered* at a higher score than it is *exited* (``suspect_enter`` >
  ``suspect_exit``, ``fake_enter`` > ``fake_exit``), so an EMA wandering
  inside the gap cannot oscillate between two verdicts
  (tests/test_streaming.py pins the no-flap property).

States escalate ``real → suspect → fake`` and de-escalate one level at a
time; a single large EMA jump may emit several transition events in one
update (each level crossed is witnessed by its own event, so downstream
consumers always see a connected path through the state graph).

Every transition is emitted as a **schema-versioned** event dict
(:data:`EVENT_SCHEMA`) so the wire format can evolve without breaking
consumers — the JSONL event-log discipline of ``obs/events.py`` applied
to the streaming subsystem.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

__all__ = ["REAL", "SUSPECT", "FAKE", "SEVERITY", "EVENT_SCHEMA",
           "VerdictThresholds", "VerdictMachine"]

REAL = "real"
SUSPECT = "suspect"
FAKE = "fake"

#: escalation order; higher = worse (stream verdict = max over tracks)
SEVERITY = {REAL: 0, SUSPECT: 1, FAKE: 2}

#: bump on any backwards-incompatible change to the event dict layout
EVENT_SCHEMA = "dfd.streaming.verdict.v1"


class VerdictThresholds:
    """Validated hysteresis threshold set (shared by every machine of a
    server, so validation happens once at config time)."""

    __slots__ = ("suspect_enter", "suspect_exit", "fake_enter", "fake_exit")

    def __init__(self, suspect_enter: float = 0.5, suspect_exit: float = 0.35,
                 fake_enter: float = 0.8, fake_exit: float = 0.65):
        self.suspect_enter = float(suspect_enter)
        self.suspect_exit = float(suspect_exit)
        self.fake_enter = float(fake_enter)
        self.fake_exit = float(fake_exit)
        if not (0.0 <= self.suspect_exit < self.suspect_enter <= 1.0):
            raise ValueError(
                f"need 0 <= suspect_exit < suspect_enter <= 1, got "
                f"exit={self.suspect_exit} enter={self.suspect_enter}")
        if not (0.0 <= self.fake_exit < self.fake_enter <= 1.0):
            raise ValueError(
                f"need 0 <= fake_exit < fake_enter <= 1, got "
                f"exit={self.fake_exit} enter={self.fake_enter}")
        if self.suspect_enter > self.fake_enter:
            raise ValueError(
                f"suspect_enter ({self.suspect_enter}) must not exceed "
                f"fake_enter ({self.fake_enter})")
        if self.suspect_exit > self.fake_exit:
            raise ValueError(
                f"suspect_exit ({self.suspect_exit}) must not exceed "
                f"fake_exit ({self.fake_exit})")

    def to_dict(self) -> Dict[str, float]:
        return {k: getattr(self, k) for k in self.__slots__}


class VerdictMachine:
    """One EMA + hysteresis state machine (per track, and one per stream).

    ``update()`` folds a window's fake score into the EMA and returns the
    (possibly empty) list of transition events it caused.  Deterministic:
    state depends only on the score sequence, never on wall time (the
    ``wall_time`` stamped into events is advisory metadata).
    """

    def __init__(self, thresholds: Optional[VerdictThresholds] = None,
                 ema_alpha: float = 0.3, min_windows: int = 1,
                 context: Optional[Dict[str, Any]] = None):
        if not 0.0 < ema_alpha <= 1.0:
            raise ValueError(f"ema_alpha must be in (0, 1], got {ema_alpha}")
        if min_windows < 1:
            raise ValueError(f"min_windows must be >= 1, got {min_windows}")
        self.thresholds = thresholds or VerdictThresholds()
        self.ema_alpha = float(ema_alpha)
        self.min_windows = int(min_windows)
        self.context = dict(context or {})
        self.state = REAL
        self.ema: Optional[float] = None
        self.windows = 0
        self.transitions = 0
        self.last_score: Optional[float] = None

    # ------------------------------------------------------------------
    def _next_state(self) -> str:
        """One hysteresis step from the current (state, ema)."""
        t, e = self.thresholds, self.ema
        if self.state == REAL:
            return SUSPECT if e >= t.suspect_enter else REAL
        if self.state == SUSPECT:
            if e >= t.fake_enter:
                return FAKE
            if e < t.suspect_exit:
                return REAL
            return SUSPECT
        # FAKE
        return SUSPECT if e < t.fake_exit else FAKE

    def update(self, score: float, *, frame_idx: Optional[int] = None,
               wall_time: Optional[float] = None) -> List[Dict[str, Any]]:
        """Fold one window score in; returns transition events (often [])."""
        score = float(score)
        self.last_score = score
        self.ema = score if self.ema is None else \
            self.ema_alpha * score + (1.0 - self.ema_alpha) * self.ema
        self.windows += 1
        if self.windows < self.min_windows:
            return []                  # EMA warms up before verdicts move
        events: List[Dict[str, Any]] = []
        # walk one level at a time so a big EMA jump still emits a
        # connected real→suspect→fake path (two events, not one leap)
        while True:
            nxt = self._next_state()
            if nxt == self.state:
                break
            event = {
                "schema": EVENT_SCHEMA,
                "event": "verdict",
                "from": self.state,
                "to": nxt,
                "ema": round(self.ema, 6),
                "score": round(score, 6),
                "windows": self.windows,
                "wall_time": time.time() if wall_time is None else wall_time,
            }
            if frame_idx is not None:
                event["frame_idx"] = int(frame_idx)
            event.update(self.context)
            events.append(event)
            self.state = nxt
            self.transitions += 1
        return events

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "ema": None if self.ema is None else round(self.ema, 6),
            "windows": self.windows,
            "transitions": self.transitions,
            "last_score": self.last_score,
        }

    # ------------------------------------------------------------------
    # durability (streaming session snapshots): unlike snapshot(), the
    # state dict is FULL precision — a restored machine must continue the
    # score sequence bit-identically to one that never stopped
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "ema": self.ema,               # unrounded: EMA continuity
            "windows": self.windows,
            "transitions": self.transitions,
            "last_score": self.last_score,
        }

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        if d["state"] not in SEVERITY:
            raise ValueError(f"unknown verdict state {d['state']!r}")
        self.state = d["state"]
        self.ema = None if d["ema"] is None else float(d["ema"])
        self.windows = int(d["windows"])
        self.transitions = int(d["transitions"])
        self.last_score = None if d.get("last_score") is None else \
            float(d["last_score"])
