"""Face localization + greedy-IoU tracking for the streaming pipeline.

Everything upstream of this repo assumed pre-extracted face crops; a live
stream delivers whole frames, so the pipeline needs (a) a *localizer*
that proposes face boxes per frame and (b) a *tracker* that strings those
boxes into stable per-face tracks the temporal windower can batch over.

The localizer is a pluggable interface because the detector model is a
deployment choice, not an architecture one:

* :class:`FullFrameLocalizer` (``"full_frame"``) — the deterministic
  built-in: one box covering the whole frame.  This reproduces today's
  pre-cropped assumption exactly (crop == frame, so window payloads are
  bit-identical to the CLI preprocess of the same frames) and is the mode
  every parity test and bench runs.
* ``"callable:<module>:<attr>"`` — the model-backed adapter slot: any
  importable ``frame -> [(box, score), ...]`` function (an ONNX/JAX face
  detector, a remote detection service client) plugs in without touching
  this module.  :func:`register_localizer` does the same for in-process
  factories.

The tracker is deliberately classical (greedy IoU association + EMA box
smoothing + birth/coast/death lifecycle — the SORT recipe minus the
Kalman filter, which EMA approximates for slow head motion): it is
deterministic given its inputs, runs in microseconds per frame on the
ingest thread, and its failure mode under missed detections is *coasting*
(keep scoring the last known box) rather than track churn, which is what
the per-track verdict EMA wants.

No jax imports — numpy only, so unit/property tests stay sub-second.
"""

from __future__ import annotations

import importlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Box", "Detection", "iou", "clip_box", "crop_box",
           "FaceLocalizer", "FullFrameLocalizer", "CallableLocalizer",
           "register_localizer", "make_localizer", "localizer_names",
           "Track", "TrackerUpdate", "GreedyIouTracker"]

#: (x1, y1, x2, y2) in pixels, half-open, x right / y down
Box = Tuple[float, float, float, float]
#: one localizer proposal: (box, confidence in [0, 1])
Detection = Tuple[Box, float]


def iou(a: Sequence[float], b: Sequence[float]) -> float:
    """Intersection-over-union of two (x1, y1, x2, y2) boxes."""
    ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
    ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
    iw, ih = max(0.0, ix2 - ix1), max(0.0, iy2 - iy1)
    inter = iw * ih
    if inter <= 0.0:
        return 0.0
    area_a = max(0.0, a[2] - a[0]) * max(0.0, a[3] - a[1])
    area_b = max(0.0, b[2] - b[0]) * max(0.0, b[3] - b[1])
    union = area_a + area_b - inter
    return inter / union if union > 0.0 else 0.0


def clip_box(box: Sequence[float], width: int, height: int) -> Box:
    x1 = min(max(box[0], 0.0), float(width))
    y1 = min(max(box[1], 0.0), float(height))
    x2 = min(max(box[2], x1), float(width))
    y2 = min(max(box[3], y1), float(height))
    return (x1, y1, x2, y2)


def crop_box(frame: np.ndarray, box: Sequence[float],
             margin: float = 0.0) -> np.ndarray:
    """Extract the (margin-expanded, clamped, integer-rounded) box from an
    (H, W, C) frame as a view.

    A full-frame box with any margin crops back to the exact frame (the
    expansion clamps away), which is what makes the ``full_frame``
    localizer's pipeline bit-identical to the pre-cropped CLI path.
    """
    h, w = frame.shape[:2]
    x1, y1, x2, y2 = box
    if margin:
        mx = (x2 - x1) * margin
        my = (y2 - y1) * margin
        x1, y1, x2, y2 = x1 - mx, y1 - my, x2 + mx, y2 + my
    x1, y1, x2, y2 = clip_box((x1, y1, x2, y2), w, h)
    # integer-round, then force ≥1 px in both dims even for a degenerate
    # box at the far edge (a jittering detector can propose x1 == w; a
    # 0-width crop would crash params.resize downstream)
    xi1 = min(int(np.floor(x1)), w - 1) if w else 0
    yi1 = min(int(np.floor(y1)), h - 1) if h else 0
    xi2 = min(max(int(np.ceil(x2)), xi1 + 1), w)
    yi2 = min(max(int(np.ceil(y2)), yi1 + 1), h)
    return frame[yi1:yi2, xi1:xi2]


# ---------------------------------------------------------------------------
# Localizer interface + registry
# ---------------------------------------------------------------------------

class FaceLocalizer:
    """``frame -> [(box, score), ...]`` with a stable ``name`` for status
    surfaces.  Implementations must be deterministic per frame (the
    tracker and every downstream parity property assume it)."""

    name = "base"

    def localize(self, frame: np.ndarray) -> List[Detection]:
        raise NotImplementedError


class FullFrameLocalizer(FaceLocalizer):
    """One box covering the whole frame — the pre-cropped-input mode."""

    name = "full_frame"

    def localize(self, frame: np.ndarray) -> List[Detection]:
        h, w = frame.shape[:2]
        return [((0.0, 0.0, float(w), float(h)), 1.0)]


class CallableLocalizer(FaceLocalizer):
    """Adapter wrapping any ``frame -> [(box, score), ...]`` callable —
    the slot a model-backed face detector plugs into."""

    def __init__(self, fn: Callable[[np.ndarray], List[Detection]],
                 name: str = "callable"):
        self._fn = fn
        self.name = name

    def localize(self, frame: np.ndarray) -> List[Detection]:
        return [(tuple(float(c) for c in box), float(score))
                for box, score in self._fn(frame)]


_REGISTRY: Dict[str, Callable[[], FaceLocalizer]] = {
    "full_frame": FullFrameLocalizer,
}
_registry_lock = threading.Lock()


def register_localizer(name: str,
                       factory: Callable[[], FaceLocalizer]) -> None:
    with _registry_lock:
        _REGISTRY[name] = factory


def localizer_names() -> List[str]:
    with _registry_lock:
        return sorted(_REGISTRY)


def make_localizer(spec: str) -> FaceLocalizer:
    """Resolve a localizer spec: a registry name, or
    ``callable:<module>:<attr>`` importing a detector function."""
    with _registry_lock:
        factory = _REGISTRY.get(spec)
    if factory is not None:
        return factory()
    if spec.startswith("callable:"):
        mod_name, _, attr = spec[len("callable:"):].partition(":")
        if not mod_name or not attr:
            raise ValueError(
                f"localizer spec {spec!r} must be callable:<module>:<attr>")
        fn = getattr(importlib.import_module(mod_name), attr)
        return CallableLocalizer(fn, name=spec)
    raise ValueError(f"unknown localizer {spec!r} "
                     f"(known: {localizer_names()} or callable:mod:attr)")


# ---------------------------------------------------------------------------
# Tracks
# ---------------------------------------------------------------------------

class Track:
    """One face across frames: EMA-smoothed box + lifecycle counters."""

    __slots__ = ("id", "box", "score", "hits", "misses", "born_frame",
                 "last_frame", "windows_scored")

    def __init__(self, track_id: int, box: Box, score: float,
                 frame_idx: int):
        self.id = track_id
        self.box: Box = tuple(float(c) for c in box)
        self.score = float(score)
        self.hits = 1
        self.misses = 0
        self.born_frame = int(frame_idx)
        self.last_frame = int(frame_idx)
        self.windows_scored = 0

    @property
    def coasting(self) -> bool:
        return self.misses > 0

    def snapshot(self) -> Dict[str, Any]:
        return {"id": self.id, "box": [round(c, 2) for c in self.box],
                "hits": self.hits, "misses": self.misses,
                "born_frame": self.born_frame,
                "last_frame": self.last_frame,
                "coasting": self.coasting}

    # -- durability: FULL precision (snapshot() rounds for display) ----
    def state_dict(self) -> Dict[str, Any]:
        return {"id": self.id, "box": list(self.box), "score": self.score,
                "hits": self.hits, "misses": self.misses,
                "born_frame": self.born_frame,
                "last_frame": self.last_frame,
                "windows_scored": self.windows_scored}

    @classmethod
    def from_state_dict(cls, d: Dict[str, Any]) -> "Track":
        t = cls(int(d["id"]), tuple(float(c) for c in d["box"]),
                float(d["score"]), int(d["born_frame"]))
        t.hits = int(d["hits"])
        t.misses = int(d["misses"])
        t.last_frame = int(d["last_frame"])
        t.windows_scored = int(d["windows_scored"])
        return t


class TrackerUpdate:
    """Result of one tracker step.  ``born`` lists EVERY new track
    (the birth ledger must balance against deaths); ``fresh`` additionally
    gates on ``min_hits`` confirmation."""

    __slots__ = ("matched", "born", "coasting", "died", "confirmed_born")

    def __init__(self, matched: List[Track], born: List[Track],
                 coasting: List[Track], died: List[Track],
                 confirmed_born: Optional[List[Track]] = None):
        self.matched = matched
        self.born = born
        self.coasting = coasting
        self.died = died
        self.confirmed_born = born if confirmed_born is None \
            else confirmed_born

    @property
    def fresh(self) -> List[Track]:
        """Tracks with a REAL detection this frame (matched, or born
        AND past min_hits confirmation) — the ones whose crop should
        enter the temporal window."""
        return self.matched + self.confirmed_born


class GreedyIouTracker:
    """Greedy IoU association with EMA box smoothing and a
    birth/coast/death lifecycle.

    * **association**: all (track, detection) pairs with IoU ≥ ``iou_min``
      are matched greedily in descending-IoU order (ties broken by track
      id then detection index, so the assignment is deterministic);
    * **smoothing**: a matched track's box moves by EMA —
      ``box = ema_alpha·det + (1-ema_alpha)·box`` — damping detector
      jitter so crops (and therefore window scores) are stable;
    * **coast**: an unmatched track keeps its last box for up to
      ``max_coast`` consecutive misses (detector flicker must not kill a
      track mid-window);
    * **death**: past ``max_coast`` misses the track is retired and
      reported in ``died`` so the windower/verdict state can be dropped;
    * **birth**: unmatched detections start new tracks; a track only
      counts as *confirmed* (``fresh``/windowable) after ``min_hits``
      matches, filtering one-frame false positives when a real detector
      is plugged in (``min_hits=1`` keeps the full-frame path immediate).
    """

    def __init__(self, iou_min: float = 0.3, ema_alpha: float = 0.6,
                 max_coast: int = 10, min_hits: int = 1):
        if not 0.0 < ema_alpha <= 1.0:
            raise ValueError(f"ema_alpha must be in (0, 1], got {ema_alpha}")
        if not 0.0 <= iou_min <= 1.0:
            raise ValueError(f"iou_min must be in [0, 1], got {iou_min}")
        self.iou_min = float(iou_min)
        self.ema_alpha = float(ema_alpha)
        self.max_coast = int(max_coast)
        self.min_hits = max(1, int(min_hits))
        self.tracks: Dict[int, Track] = {}
        self.next_id = 0
        self.born_total = 0
        self.died_total = 0

    # ------------------------------------------------------------------
    def update(self, frame_idx: int,
               detections: Sequence[Detection]) -> TrackerUpdate:
        tracks = list(self.tracks.values())
        used_tracks, used_dets = set(), set()
        matched: List[Track] = []
        if tracks and detections:
            # full IoU matrix in one numpy pass (ISSUE 20): float64
            # arithmetic in the exact order of the scalar iou(), so every
            # candidate value — and therefore every greedy assignment —
            # is bit-identical to the historical nested-loop version
            tb = np.asarray([t.box for t in tracks], np.float64)
            db = np.asarray([d[0] for d in detections], np.float64)
            ix1 = np.maximum(tb[:, None, 0], db[None, :, 0])
            iy1 = np.maximum(tb[:, None, 1], db[None, :, 1])
            ix2 = np.minimum(tb[:, None, 2], db[None, :, 2])
            iy2 = np.minimum(tb[:, None, 3], db[None, :, 3])
            inter = np.maximum(0.0, ix2 - ix1) * np.maximum(0.0, iy2 - iy1)
            area_t = np.maximum(0.0, tb[:, 2] - tb[:, 0]) * \
                np.maximum(0.0, tb[:, 3] - tb[:, 1])
            area_d = np.maximum(0.0, db[:, 2] - db[:, 0]) * \
                np.maximum(0.0, db[:, 3] - db[:, 1])
            union = area_t[:, None] + area_d[None, :] - inter
            # inter > 0 implies union >= inter > 0 (each area bounds the
            # intersection), so the guarded divide mirrors iou()'s early
            # returns exactly
            with np.errstate(divide="ignore", invalid="ignore"):
                v = np.where(inter > 0.0, inter / union, 0.0)
            ti, dj = np.nonzero(v >= self.iou_min)
            if ti.size:
                tids = np.asarray([t.id for t in tracks], np.int64)[ti]
                # lexsort keys are LAST-is-primary: -iou descending, then
                # track id, then detection index — the tuple order
                # pairs.sort() used on (-iou, t.id, di)
                order = np.lexsort((dj, tids, -v[ti, dj]))
                a = self.ema_alpha
                for k in order:
                    tid, di = int(tids[k]), int(dj[k])
                    if tid in used_tracks or di in used_dets:
                        continue
                    used_tracks.add(tid)
                    used_dets.add(di)
                    t = self.tracks[tid]
                    box, score = detections[di]
                    t.box = tuple(a * float(d) + (1.0 - a) * p
                                  for d, p in zip(box, t.box))
                    t.score = float(score)
                    t.hits += 1
                    t.misses = 0
                    t.last_frame = int(frame_idx)
                    if t.hits >= self.min_hits:
                        matched.append(t)
        born: List[Track] = []
        confirmed_born: List[Track] = []
        for di, (box, score) in enumerate(detections):
            if di in used_dets:
                continue
            t = Track(self.next_id, box, score, frame_idx)
            self.next_id += 1
            self.tracks[t.id] = t
            self.born_total += 1
            born.append(t)
            if t.hits >= self.min_hits:
                confirmed_born.append(t)
        coasting: List[Track] = []
        died: List[Track] = []
        for t in tracks:
            if t.id in used_tracks:
                continue
            t.misses += 1
            if t.misses > self.max_coast:
                died.append(t)
                del self.tracks[t.id]
                self.died_total += 1
            else:
                coasting.append(t)
        return TrackerUpdate(matched, born, coasting, died,
                     confirmed_born)

    # ------------------------------------------------------------------
    def active(self) -> List[Track]:
        return sorted(self.tracks.values(), key=lambda t: t.id)

    def snapshot(self) -> List[Dict[str, Any]]:
        return [t.snapshot() for t in self.active()]

    # ------------------------------------------------------------------
    # durability (streaming session snapshots)
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {"next_id": self.next_id, "born_total": self.born_total,
                "died_total": self.died_total,
                "tracks": [t.state_dict() for t in self.active()]}

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        self.next_id = int(d["next_id"])
        self.born_total = int(d["born_total"])
        self.died_total = int(d["died_total"])
        self.tracks = {}
        for td in d["tracks"]:
            t = Track.from_state_dict(td)
            self.tracks[t.id] = t
