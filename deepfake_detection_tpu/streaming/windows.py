"""Temporal sliding-window batcher: per-track crops → flagship-shaped
clips → serving-engine buckets.

The 12-channel flagship scores ``img_num`` *distinct* frames channel-
concatenated into one clip; a live track delivers one crop per frame.
This module closes the gap:

* :class:`TrackWindower` buffers the last crops of each track and emits a
  window of ``img_num`` frames every ``hop`` pushes, taking every
  ``stride``-th frame so a window can span more wall time than
  ``img_num`` consecutive frames.  ``hop < img_num·stride`` overlaps
  windows (denser verdicts), ``hop == img_num·stride`` tiles them.
* :func:`build_payload` turns a window's uint8 canvases into the serving
  wire format: the float32 wire runs the exact CLI preprocess
  (``params.normalize_concat``) host-side, so a window's score is
  bit-identical to scoring the same clip through ``runners/test.py``;
  the uint8 wire ships channel-concatenated uint8 and normalizes inside
  the engine's multi-frame program.
* :class:`WindowDispatcher` feeds windows into the serving micro-batcher
  under **bounded per-stream queues with drop-oldest backpressure**: a
  slow device must shed the *stalest* windows (their verdict value decays
  fastest) while frames keep flowing — an unbounded queue would instead
  grow a backlog whose scores arrive too late to matter.  Batcher-level
  load shedding (``QueueFull``) and per-request deadlines are counted,
  never silent.

One dispatcher (2 threads) serves every stream in the process: a submit
thread drains the per-stream deques round-robin (no stream can starve
another), and a collector thread blocks on results in submission order —
the engine completes batches FIFO, so head-of-line blocking here is
bounded by one request deadline.
"""

from __future__ import annotations

import base64
import collections
import logging
import queue
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from . import ring as ring_mod

__all__ = ["Window", "TrackWindower", "build_payload", "WindowJob",
           "WindowDispatcher"]

_logger = logging.getLogger(__name__)


class Window:
    """One emitted clip: ``img_num`` uint8 canvases + their frame indices.

    On the frame-once path (ISSUE 20) the frames are views into the
    per-track :class:`~.ring.CanvasRing`; ``digests`` carries the cached
    per-crop sha256s (frame order) for window content keys, and ``refs``
    the ring pins this window took at emission — whoever consumes the
    window releases them (the session wraps them in a ``RingLease``).
    """

    __slots__ = ("track_id", "frames", "frame_idxs", "window_idx",
                 "digests", "refs")

    def __init__(self, track_id: int, frames: List[np.ndarray],
                 frame_idxs: Tuple[int, ...], window_idx: int,
                 digests: Optional[Tuple[bytes, ...]] = None,
                 refs: Optional[List[Any]] = None):
        self.track_id = track_id
        self.frames = frames
        self.frame_idxs = frame_idxs
        self.window_idx = window_idx
        self.digests = digests
        self.refs = refs


class TrackWindower:
    """Per-track sliding windows of ``img_num`` distinct frames.

    ``stride`` is the in-window frame spacing (1 = consecutive crops);
    ``hop`` is how many pushes separate consecutive emissions (default
    ``img_num * stride``: non-overlapping tiling).  Window ``k`` holds the
    newest crop plus the ``img_num - 1`` crops ``stride`` pushes apart
    behind it, oldest first — the channel order ``MultiConcate`` gives
    training clips.
    """

    def __init__(self, img_num: int, stride: int = 1, hop: int = 0,
                 digest_frames: bool = False):
        if img_num < 1:
            raise ValueError(f"img_num must be >= 1, got {img_num}")
        if stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        self.img_num = int(img_num)
        self.stride = int(stride)
        self.hop = int(hop) if hop else self.img_num * self.stride
        if self.hop < 1:
            raise ValueError(f"hop must be >= 1, got {self.hop}")
        self.span = (self.img_num - 1) * self.stride + 1
        # frame-once mode: restored snapshot frames get their canonical
        # digest computed once here, so post-restore windows stay keyable
        self.digest_frames = bool(digest_frames)
        # entries: (frame_idx, canvas, digest|None, FrameRef|None)
        self._buffers: Dict[int, Deque[Tuple[int, np.ndarray,
                                             Optional[bytes], Any]]] = {}
        self._pushes: Dict[int, int] = {}
        self._emitted: Dict[int, int] = {}
        self._last_emit_push: Dict[int, int] = {}

    # ------------------------------------------------------------------
    def push(self, track_id: int, frame_idx: int, canvas: np.ndarray,
             digest: Optional[bytes] = None,
             ref: Any = None) -> Optional[Window]:
        """Add one crop; returns a :class:`Window` when one is due.

        ``digest``/``ref`` ride along on the frame-once path: the buffer
        takes ownership of one reference on ``ref`` and releases it when
        the entry falls out of the span (or the track drops)."""
        buf = self._buffers.get(track_id)
        if buf is None:
            buf = self._buffers[track_id] = collections.deque()
            self._pushes[track_id] = 0
            self._emitted[track_id] = 0
        buf.append((int(frame_idx), canvas, digest, ref))
        if len(buf) > self.span:
            old = buf.popleft()
            if old[3] is not None:
                old[3].decref()
        self._pushes[track_id] += 1
        pushes = self._pushes[track_id]
        if len(buf) < self.span:
            return None
        emitted = self._emitted[track_id]
        # first window fires on the push that fills the span; after that,
        # every `hop` pushes
        if emitted and pushes - self._last_emit_push[track_id] < self.hop:
            return None
        self._emitted[track_id] = emitted + 1
        self._last_emit_push[track_id] = pushes
        picked = [buf[i] for i in range(self.span - 1, -1, -self.stride)]
        picked.reverse()                            # oldest → newest
        idxs = tuple(e[0] for e in picked)
        frames = [e[1] for e in picked]
        digests: Optional[Tuple[bytes, ...]] = tuple(
            e[2] for e in picked)
        if any(d is None for d in digests):
            digests = None
        refs = [e[3] for e in picked if e[3] is not None]
        for r in refs:                              # pin rows for the
            r.incref()                              # window's lifetime
        return Window(track_id, frames, idxs, emitted, digests,
                      refs or None)

    def newest(self, track_id: int) -> Optional[Tuple[int, np.ndarray,
                                                      Optional[bytes],
                                                      Any]]:
        """The track's most recent buffer entry (duplicate-frame reuse)."""
        buf = self._buffers.get(track_id)
        return buf[-1] if buf else None

    def drop_track(self, track_id: int) -> None:
        buf = self._buffers.pop(track_id, None)
        if buf:
            for e in buf:
                if e[3] is not None:
                    e[3].decref()
        self._pushes.pop(track_id, None)
        self._emitted.pop(track_id, None)
        self._last_emit_push.pop(track_id, None)

    def buffered_tracks(self) -> List[int]:
        return sorted(self._buffers)

    # ------------------------------------------------------------------
    # durability (streaming session snapshots): window-POSITION state —
    # pushes/emitted/last-emit counters AND the buffered crops (base64
    # raw bytes + shape), so a restored track continues mid-window and
    # emits its next window at exactly the push an unkilled server would
    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        tracks = {}
        for tid, buf in self._buffers.items():
            tracks[str(tid)] = {
                "pushes": self._pushes[tid],
                "emitted": self._emitted[tid],
                "last_emit_push": self._last_emit_push.get(tid),
                "frames": [
                    {"frame_idx": fi,
                     "shape": list(np.shape(canvas)),
                     "dtype": str(np.asarray(canvas).dtype),
                     "data_b64": base64.b64encode(
                         np.ascontiguousarray(canvas).tobytes()).decode()}
                    for fi, canvas, _digest, _ref in buf],
            }
        return {"img_num": self.img_num, "stride": self.stride,
                "hop": self.hop, "tracks": tracks}

    def load_state_dict(self, d: Dict[str, Any]) -> None:
        if (int(d["img_num"]), int(d["stride"]), int(d["hop"])) != \
                (self.img_num, self.stride, self.hop):
            raise ValueError(
                f"windower geometry changed across restart: snapshot has "
                f"img_num={d['img_num']} stride={d['stride']} "
                f"hop={d['hop']}, server runs img_num={self.img_num} "
                f"stride={self.stride} hop={self.hop}")
        for tid in list(self._buffers):
            self.drop_track(tid)                   # release any ring pins
        self._buffers.clear()
        self._pushes.clear()
        self._emitted.clear()
        self._last_emit_push.clear()
        for tid_s, td in d["tracks"].items():
            tid = int(tid_s)
            buf = collections.deque()
            for fr in td["frames"]:
                canvas = np.frombuffer(
                    base64.b64decode(fr["data_b64"]),
                    dtype=np.dtype(fr["dtype"])).reshape(fr["shape"])
                # snapshots predate digests (schema v1 unchanged): the
                # canonical digest is recomputed once at restore so
                # post-restore windows stay cache-keyable
                digest = ring_mod.frame_digest(canvas) \
                    if self.digest_frames else None
                buf.append((int(fr["frame_idx"]), canvas, digest, None))
            self._buffers[tid] = buf
            self._pushes[tid] = int(td["pushes"])
            self._emitted[tid] = int(td["emitted"])
            if td.get("last_emit_push") is not None:
                self._last_emit_push[tid] = int(td["last_emit_push"])


def build_payload(frames: List[np.ndarray], wire: str,
                  on_elide: Optional[Callable[[int], None]] = None
                  ) -> np.ndarray:
    """Window frames (uint8 HWC canvases) → one wire-format sample.

    float32: exact CLI preprocess per frame + channel concat
    (``params.normalize_concat``) — scores are bit-identical to the CLI
    path because the engine's float32 buckets ARE the CLI program.
    uint8: channel-concat only; normalization runs inside the engine's
    multi-frame device program.  ``np.concatenate`` copies its inputs
    regardless of contiguity, so the historical per-frame
    ``ascontiguousarray`` staging copy is elided (counted via
    ``on_elide`` for the frames that would actually have copied —
    non-contiguous crops).
    """
    from ..params import normalize_concat
    if wire == "float32":
        return normalize_concat(frames)
    if on_elide is not None:
        elided = sum(1 for f in frames if not f.flags.c_contiguous)
        if elided:
            on_elide(elided)
    return np.concatenate(frames, axis=-1)


# ---------------------------------------------------------------------------
# Dispatch: bounded per-stream queues → micro-batcher → result collection
# ---------------------------------------------------------------------------

class WindowJob:
    """One window queued for scoring, with enough context for the result
    callback to route it back to its stream/track verdict state.

    ``content_key`` (when the verdict cache is live) is the window's
    ``(content_hash, phash)`` identity for ``MicroBatcher.submit``;
    ``lease`` holds the ring pins released on every terminal path;
    ``cache_hit`` is set by the collector when the request resolved from
    the cache instead of a device bucket."""

    __slots__ = ("stream_id", "track_id", "window_idx", "frame_idxs",
                 "payload", "enqueue_t", "context", "attempts",
                 "content_key", "lease", "cache_hit")

    def __init__(self, stream_id: str, track_id: int, window_idx: int,
                 frame_idxs: Tuple[int, ...], payload: np.ndarray,
                 context: Any = None, content_key: Any = None,
                 lease: Any = None):
        self.stream_id = stream_id
        self.track_id = track_id
        self.window_idx = window_idx
        self.frame_idxs = frame_idxs
        self.payload = payload
        self.enqueue_t = time.monotonic()
        self.context = context
        self.attempts = 0
        self.content_key = content_key
        self.lease = lease
        self.cache_hit = False


class WindowDispatcher:
    """Round-robin submit + in-order collect between every stream's
    window queue and the serving micro-batcher.

    ``on_result(job, scores, error)`` is invoked from the collector
    thread: exactly one of ``scores`` (np.ndarray softmax row) and
    ``error`` (Exception) is not None.  Per-stream queues hold at most
    ``max_pending`` windows; a push beyond that drops the OLDEST pending
    window (counted via ``on_drop(job, reason)``) — under sustained
    overload the newest evidence wins.
    """

    def __init__(self, batcher, *, max_pending: int = 4,
                 request_timeout_s: float = 10.0,
                 shed_retries: int = 1,
                 on_result: Callable[[WindowJob, Optional[np.ndarray],
                                      Optional[BaseException]], None],
                 on_drop: Optional[Callable[[WindowJob, str], None]] = None):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.batcher = batcher
        self.max_pending = int(max_pending)
        #: bounded wait for a queue slot before drop-oldest fires (see
        #: push()); 0 restores the historical drop-immediately behavior
        self.push_grace_s = 0.02
        self.request_timeout_s = float(request_timeout_s)
        self.shed_retries = max(0, int(shed_retries))
        self._on_result = on_result
        self._on_drop = on_drop or (lambda job, reason: None)
        self._queues: "collections.OrderedDict[str, Deque[WindowJob]]" = \
            collections.OrderedDict()
        self._cv = threading.Condition()
        self._inflight: "queue.Queue[Tuple[WindowJob, Any]]" = queue.Queue()
        self._stop = threading.Event()
        self._submit_thread: Optional[threading.Thread] = None
        self._collect_thread: Optional[threading.Thread] = None
        self.submitted_total = 0
        self.dropped_total = 0
        self.shed_total = 0
        self.failed_total = 0
        self.scored_total = 0
        self.cache_hit_total = 0

    # ------------------------------------------------------------------
    def start(self) -> None:
        assert self._submit_thread is None, "dispatcher already started"
        self._submit_thread = threading.Thread(
            target=self._submit_loop, name="stream-window-submit",
            daemon=True)
        self._collect_thread = threading.Thread(
            target=self._collect_loop, name="stream-window-collect",
            daemon=True)
        self._submit_thread.start()
        self._collect_thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        for t in (self._submit_thread, self._collect_thread):
            if t is not None:
                t.join(timeout=5.0)
        self._submit_thread = self._collect_thread = None

    # ------------------------------------------------------------------
    @staticmethod
    def _release_lease(job) -> None:
        """Terminal paths free the job's ring pins; idempotent (the
        engine's staging gather may already have consumed them)."""
        lease = getattr(job, "lease", None)
        if lease is not None:
            job.lease = None
            lease.release()

    def on_result(self, job: WindowJob, scores, error) -> None:
        """Guarded callback: an exception in the sink (event-log disk
        full, plugin bug) must not kill the dispatcher threads — every
        stream's verdicts would silently freeze while /healthz stays
        green."""
        self._release_lease(job)
        try:
            self._on_result(job, scores, error)
        except Exception:                          # noqa: BLE001
            _logger.exception("on_result sink failed for stream %s "
                              "window %d", job.stream_id, job.window_idx)

    def on_drop(self, job: WindowJob, reason: str) -> None:
        self._release_lease(job)
        try:
            self._on_drop(job, reason)
        except Exception:                          # noqa: BLE001
            _logger.exception("on_drop sink failed for stream %s",
                              job.stream_id)

    # ------------------------------------------------------------------
    def push(self, job: WindowJob) -> None:
        """Queue a window (ingest thread); drops oldest past the
        per-stream bound.

        A full queue first gets a short bounded grace (``push_grace_s``)
        for the submit thread to drain a slot: the frame-once assembly
        path emits a chunk's windows microseconds apart, so without the
        grace a burst smaller than the engine's throughput would shed
        windows purely because the submit thread hadn't had a GIL slice
        yet (the historical per-window copy chain paced this
        accidentally).  Under sustained overload the queue is still full
        when the grace lapses and the oldest window drops, exactly as
        before — bounded wait, never unbounded blocking."""
        deadline = None
        while True:
            with self._cv:
                q = self._queues.get(job.stream_id)
                if q is None:
                    q = self._queues[job.stream_id] = collections.deque()
                if len(q) < self.max_pending:
                    q.append(job)
                    self._cv.notify()
                    return
                now = time.monotonic()
                if deadline is None:
                    # no submit thread (unit tests, post-stop) ⇒ nothing
                    # will ever drain: drop immediately, as before
                    deadline = now + (self.push_grace_s
                                      if self._submit_thread is not None
                                      else 0.0)
                if now >= deadline:
                    dropped = q.popleft()
                    self.dropped_total += 1
                    q.append(job)
                    self._cv.notify()
                    break
            time.sleep(0.0005)
        self.on_drop(dropped, "backpressure")

    def drop_stream(self, stream_id: str) -> int:
        """Discard a closed stream's pending windows; returns the count."""
        with self._cv:
            q = self._queues.pop(stream_id, None)
        if not q:
            return 0
        for job in q:
            self.on_drop(job, "stream_closed")
        self.dropped_total += len(q)
        return len(q)

    def pending(self) -> int:
        with self._cv:
            return sum(len(q) for q in self._queues.values())

    # ------------------------------------------------------------------
    def _next_job(self) -> Optional[WindowJob]:
        """Round-robin pop: take from the first non-empty stream queue,
        then rotate it to the back so no stream can starve the rest."""
        with self._cv:
            while not self._stop.is_set():
                for sid in list(self._queues):
                    q = self._queues[sid]
                    if q:
                        job = q.popleft()
                        self._queues.move_to_end(sid)
                        return job
                self._cv.wait(timeout=0.1)
        return None

    def _submit_loop(self) -> None:
        from ..serving.batcher import QueueFull
        while not self._stop.is_set():
            job = self._next_job()
            if job is None:
                return
            try:
                if job.content_key is not None:
                    req = self.batcher.submit(
                        job.payload, timeout_s=self.request_timeout_s,
                        content_key=job.content_key)
                else:
                    req = self.batcher.submit(
                        job.payload, timeout_s=self.request_timeout_s)
            except QueueFull:
                if job.attempts < self.shed_retries:
                    # one paced retry before giving the window up: a shed
                    # is usually a transient spike, and the job goes back
                    # to the FRONT of its stream queue (still the oldest
                    # evidence there) while the backoff lets a batch
                    # drain.  Only if that queue still exists — re-
                    # creating one for a stream drop_stream just removed
                    # would leak the entry and score into a dead session.
                    requeued = False
                    with self._cv:
                        q = self._queues.get(job.stream_id)
                        if q is not None:
                            job.attempts += 1
                            q.appendleft(job)
                            requeued = True
                    if requeued:
                        time.sleep(0.005)
                        continue
                    self.dropped_total += 1
                    self.on_drop(job, "stream_closed")
                    continue
                self.shed_total += 1
                self.on_drop(job, "shed")
                continue
            except Exception as e:                 # noqa: BLE001
                self.failed_total += 1
                self.on_result(job, None, e)
                continue
            self.submitted_total += 1
            self._inflight.put((job, req))

    def _collect_loop(self) -> None:
        while True:
            try:
                job, req = self._inflight.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                scores = req.result(timeout=self.request_timeout_s + 5.0)
            except Exception as e:                 # noqa: BLE001
                self.failed_total += 1
                self.on_result(job, None, e)
                continue
            if getattr(req, "from_cache", False):
                self.cache_hit_total += 1
                job.cache_hit = True
            else:
                self.scored_total += 1
            self.on_result(job, np.asarray(scores), None)
