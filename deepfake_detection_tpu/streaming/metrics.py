"""Streaming observability: the ``dfd_streaming_*`` Prometheus catalog.

Same construction as ``serving/metrics.py`` (stdlib counters +
:class:`LatencyHistogram`, rendered through the shared
``utils/prometheus.py`` text renderer); the streaming front end serves
this catalog concatenated after the serving one on ``GET /metrics``, so
one scrape sees the whole pipeline: HTTP ingest → decode → track →
window → micro-batcher → device.

Stage histograms follow a frame/window's life:

* ``decode`` — chunk bytes → uint8 frames (native pool or PIL);
* ``track`` — localize + tracker update + crop + canvas per frame;
* ``assemble`` — window emission → job dispatched (key + payload);
* ``score`` — window queued → softmax row back (queue + device);
* ``ingest`` — whole ``POST /streams/<id>/frames`` handler.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..utils.metrics import LatencyHistogram
from ..utils.prometheus import Counter as _Counter
from ..utils.prometheus import PromText

__all__ = ["StreamingMetrics", "STAGES"]

_PREFIX = "dfd_streaming"

#: same sub-ms-resolving bounds as serving — ingest stages are host work
_BOUNDS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
           0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

STAGES = ("decode", "track", "assemble", "score", "ingest")


class StreamingMetrics:
    """One registry per streaming server process."""

    def __init__(self):
        self.latency: Dict[str, LatencyHistogram] = {
            s: LatencyHistogram(_BOUNDS) for s in STAGES}
        self.streams_opened_total = _Counter()
        self.streams_closed_total = _Counter()
        self.streams_evicted_total = _Counter()
        self.frames_ingested_total = _Counter()
        self.frames_decode_errors_total = _Counter()
        self.chunks_total = _Counter()
        self.tracks_born_total = _Counter()
        self.tracks_died_total = _Counter()
        self.windows_emitted_total = _Counter()
        self.windows_scored_total = _Counter()
        self.windows_dropped_total = _Counter()    # drop-oldest backpressure
        self.windows_shed_total = _Counter()       # batcher QueueFull
        self.windows_failed_total = _Counter()     # deadline / engine error
        self.windows_cache_hit_total = _Counter()  # resolved from the
        # verdict cache (content-identical clip scored before) — never
        # entered a device bucket
        self.windows_dup_elided_total = _Counter()  # clip content identical
        # to the track's previous window (dedup_frames): submission skipped
        self.frames_dup_elided_total = _Counter()  # encoded bytes identical
        # to the previous frame (dedup_frames): decode skipped
        self.canvas_copies_elided_total = _Counter()  # redundant host
        # staging work skipped (already-contiguous crops; duplicate-frame
        # canvas reuse under dedup_frames)
        self.ring_overflow_total = _Counter()      # crop-ring pool
        # exhausted: counted standalone-row fallback (never a stall)
        self.demux_failures_total = _Counter()     # ffmpeg died mid-stream
        self.streams_restored_total = _Counter()   # sessions resumed from
        # a state-dir snapshot after a server bounce
        self.streams_migrated_out_total = _Counter()   # sessions exported
        # to another replica (fleet drain; ISSUE 15)
        self.streams_migrated_in_total = _Counter()    # sessions restored
        # FROM another replica via POST /streams/restore
        self.state_errors_total = _Counter()       # snapshot save/restore
        # failures (corrupt/stale state files, unwritable dir)
        self.verdict_transitions_total: Dict[str, _Counter] = {}
        self._verdict_lock = threading.Lock()
        self.active_streams = 0                    # gauge (manager-owned)
        self.active_tracks = 0                     # gauge (manager-owned)

    # ------------------------------------------------------------------
    def count_transition(self, to_state: str) -> None:
        with self._verdict_lock:
            c = self.verdict_transitions_total.get(to_state)
            if c is None:
                c = self.verdict_transitions_total[to_state] = _Counter()
        c.inc()

    # ------------------------------------------------------------------
    def render_prometheus(self) -> str:
        doc = PromText(_PREFIX)
        counter, gauge = doc.counter, doc.gauge
        counter("streams_opened_total", "Stream sessions created",
                self.streams_opened_total.value)
        counter("streams_closed_total", "Stream sessions closed by clients",
                self.streams_closed_total.value)
        counter("streams_evicted_total", "Stream sessions evicted idle "
                "(TTL)", self.streams_evicted_total.value)
        counter("chunks_total", "Frame chunks accepted over HTTP",
                self.chunks_total.value)
        counter("frames_ingested_total", "Frames decoded into the pipeline",
                self.frames_ingested_total.value)
        counter("frames_decode_errors_total", "Frames dropped undecodable",
                self.frames_decode_errors_total.value)
        counter("tracks_born_total", "Face tracks born",
                self.tracks_born_total.value)
        counter("tracks_died_total", "Face tracks retired (coast budget "
                "exhausted)", self.tracks_died_total.value)
        counter("windows_emitted_total", "Temporal windows emitted by the "
                "windower", self.windows_emitted_total.value)
        counter("windows_scored_total", "Windows scored by the engine",
                self.windows_scored_total.value)
        counter("windows_dropped_total", "Windows dropped by per-stream "
                "drop-oldest backpressure or stream close",
                self.windows_dropped_total.value)
        counter("windows_shed_total", "Windows shed by the micro-batcher "
                "(queue full)", self.windows_shed_total.value)
        counter("windows_failed_total", "Windows failed (deadline or "
                "engine error)", self.windows_failed_total.value)
        counter("windows_cache_hit_total", "Windows resolved from the "
                "verdict cache (never entered a bucket)",
                self.windows_cache_hit_total.value)
        counter("windows_dup_elided_total", "Windows skipped as exact "
                "duplicates of the track's previous window",
                self.windows_dup_elided_total.value)
        counter("frames_dup_elided_total", "Frames whose decode was "
                "skipped as byte-identical to their predecessor",
                self.frames_dup_elided_total.value)
        counter("canvas_copies_elided_total", "Redundant host canvas "
                "staging skipped (contiguous crops, duplicate-frame "
                "reuse)", self.canvas_copies_elided_total.value)
        counter("ring_overflow_total", "Crop-ring pool exhaustions "
                "(counted standalone-row fallback)",
                self.ring_overflow_total.value)
        counter("demux_failures_total", "ffmpeg demuxer deaths surfaced "
                "as per-stream errors (422 + demuxer reset)",
                self.demux_failures_total.value)
        counter("streams_restored_total", "Stream sessions resumed from "
                "a state-dir snapshot", self.streams_restored_total.value)
        counter("streams_migrated_out_total", "Stream sessions exported "
                "to another replica (fleet drain: quiesce -> snapshot "
                "-> detach)", self.streams_migrated_out_total.value)
        counter("streams_migrated_in_total", "Stream sessions restored "
                "from another replica's snapshot (POST /streams/restore)",
                self.streams_migrated_in_total.value)
        counter("state_errors_total", "Session snapshot save/restore "
                "failures (corrupt or unwritable state files)",
                self.state_errors_total.value)
        doc.header("verdict_transitions_total",
                   "Verdict state transitions by destination state",
                   "counter")
        with self._verdict_lock:
            items = sorted((k, c.value) for k, c in
                           self.verdict_transitions_total.items())
        for state, value in items:
            doc.sample("verdict_transitions_total", f'{{to="{state}"}}',
                       value)
        gauge("active_streams", "Live stream sessions",
              self.active_streams)
        gauge("active_tracks", "Live face tracks across all streams",
              self.active_tracks)
        for stage in STAGES:
            doc.histogram("latency_seconds", "Per-stage streaming latency",
                          self.latency[stage], labels=f'stage="{stage}"')
        return doc.render()
