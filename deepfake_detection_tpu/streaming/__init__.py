"""Streaming-video scoring subsystem: live streams in, verdicts out.

Pipeline (one process, in front of the serving engine):

``POST /streams/<id>/frames`` chunks → decode (native pool) →
face localize + greedy-IoU track (``tracker``) → per-track temporal
windows of ``img_num`` distinct frames (``windows``) → serving engine's
AOT-warmed buckets → EMA + hysteresis verdict machines (``verdict``) →
schema-versioned events + ``/metrics``.

Entry point: ``python -m deepfake_detection_tpu.runners.stream``.

PEP-562 lazy exports (the ``obs/`` idiom): importing the package does not
pull jax/PIL — ``tracker``/``verdict``/``windows`` unit tests stay cheap
and jax-free.
"""

from __future__ import annotations

_LAZY = {
    "FaceLocalizer": "tracker",
    "FullFrameLocalizer": "tracker",
    "CallableLocalizer": "tracker",
    "GreedyIouTracker": "tracker",
    "make_localizer": "tracker",
    "register_localizer": "tracker",
    "iou": "tracker",
    "crop_box": "tracker",
    "VerdictMachine": "verdict",
    "VerdictThresholds": "verdict",
    "TrackWindower": "windows",
    "WindowDispatcher": "windows",
    "WindowJob": "windows",
    "build_payload": "windows",
    "CanvasRing": "ring",
    "FrameRef": "ring",
    "FrameStack": "ring",
    "RingLease": "ring",
    "frame_digest": "ring",
    "window_key": "ring",
    "StreamingMetrics": "metrics",
    "StreamManager": "ingest",
    "StreamSession": "ingest",
    "StreamServer": "ingest",
    "make_stream_server": "ingest",
    "FfmpegDemuxer": "ingest",
    "parse_verdict_vector": "ingest",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
