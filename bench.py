"""Benchmark: train throughput (frames/sec/chip) on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...,
"rows": [...]} — the headline metric is EfficientNet-B4 (the north-star
benchmark model) and ``rows`` carries the full measured config matrix
(VERDICT r3 item 1): B4 380², the flagship ``efficientnet_deepfake_v4``
12×600² (with an OOM ladder over batch/remat), ViT-B/16 224² with both
dense and Pallas-flash attention, a forward-only B4 inference row
(the reference serves inference from the same backbone, test.py), and
the temporal-extension TimeSformer on 4-frame clips (last in the
matrix, so a budget truncation never costs a reference-parity row).

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
MFU / 0.70 — the fraction of the driver-set north-star target of ≥70% MFU
(BASELINE.json) achieved by the measured step time.  FLOPs come from XLA's
own cost analysis of the compiled train step; peak chip FLOPs from the
device kind.

Env overrides: any of BENCH_MODEL/BENCH_BATCH/BENCH_SIZE/BENCH_CHANS/
BENCH_ATTN/BENCH_REMAT pins a single custom config (skipping the matrix);
BENCH_STEPS sets measured steps in either mode; BENCH_MATRIX=0 runs the
headline config only; BENCH_MATRIX_BUDGET caps the matrix's own wall-time
(default 1200 s, measured from after the headline config — later configs
are skipped, recorded as such, once exceeded).

Robustness (rounds 1-3 postmortem): the ENTIRE run — backend init, model
init, lower/compile, measurement — executes in a worker thread watched by
the main thread.  Transient TPU-side faults (round 2: "remote_compile ...
Connection refused" during model init) are retried once; a second fault or
a hang past BENCH_RUN_TIMEOUT (default 2400 s) re-execs the process with a
pure-CPU JAX env so a JSON line is ALWAYS produced; phase progress goes to
stderr so a slow compile is distinguishable from a hang.  The CPU fallback
embeds the last chip-verified TPU row set verbatim so the artifact always
carries real TPU numbers.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from types import SimpleNamespace

_T0 = time.perf_counter()

# persistent compilation cache: retried/fallback runs and the driver's own
# invocation share compiles (TPU compiles through the relay are slow)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


def _fail_json(stage: str, err: str) -> None:
    print(json.dumps({
        "metric": "train_throughput_error", "value": 0.0,
        "unit": "frames/sec/chip", "vs_baseline": 0.0,
        "error_stage": stage, "error": err[:500],
    }), flush=True)


def _reexec_cpu(reason: str) -> None:
    """Replace this process with a pure-CPU run of the same script."""
    _log(f"falling back to CPU: {reason}")
    env = dict(os.environ)
    env["_BENCH_CPU_FALLBACK"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    # the CPU fallback gets its own fixed budget — inheriting a large
    # TPU-harvest BENCH_RUN_TIMEOUT would let watchdog+fallback overrun
    # any outer supervisor (the chip battery's stage timeout)
    env["BENCH_RUN_TIMEOUT"] = "900"
    # sitecustomize registers the axon TPU plugin (and may block) whenever
    # this var is set — clear it so the fallback interpreter starts clean
    env.pop("PALLAS_AXON_POOL_IPS", None)
    sys.stderr.flush()
    os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)


def _init_backend():
    """Return jax.devices(), with watchdog + CPU fallback on error/hang."""
    import threading

    box: dict = {}

    def probe() -> None:
        try:
            import jax
            if os.environ.get("_BENCH_CPU_FALLBACK"):
                # env JAX_PLATFORMS=cpu is NOT enough: the sitecustomize's
                # axon register() overrides platform selection at interpreter
                # start; only a post-import config.update wins (same cure as
                # tests/conftest.py:19)
                jax.config.update("jax_platforms", "cpu")
            box["devices"] = jax.devices()
        except BaseException as e:  # noqa: BLE001 — must survive anything
            box["error"] = repr(e)

    timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", 240))
    _log(f"initializing jax backend (watchdog {timeout:.0f}s) ...")
    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        if os.environ.get("_BENCH_CPU_FALLBACK"):
            _fail_json("backend_init", "CPU backend init hung")
            os._exit(1)
        _reexec_cpu(f"backend init exceeded {timeout:.0f}s")
    if "error" in box:
        if os.environ.get("_BENCH_CPU_FALLBACK"):
            _fail_json("backend_init", box["error"])
            os._exit(1)
        _reexec_cpu(f"backend init failed: {box['error']}")
    _log(f"devices: {box['devices']}")
    return box["devices"]


# bf16 peak FLOPs/s per chip by device kind (public spec sheets)
_PEAK_FLOPS = {
    "TPU v2": 22.5e12, "TPU v3": 61.5e12 / 2, "TPU v4": 137.5e12 * 2,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 229.5e12 * 2,
    "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
    "TPU v7": 2307e12, "cpu": 1e11,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in _PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v
    for k, v in _PEAK_FLOPS.items():
        if k.lower() in kind.lower():
            return v
    return 275e12   # unknown TPU: assume v4-class


def _probe_execution(devices) -> None:
    """Fail fast if the backend lists a device but can't actually run.

    Round-3 postmortem: during an axon relay outage ``jax.devices()``
    returns [TPU v5 lite0] instantly while the first *execution* blocks
    forever — init watchdogs never fire and the run eats the full
    BENCH_RUN_TIMEOUT before falling back.  A tiny matmul with a short
    watchdog converts that 15-minute stall into a 2-minute CPU fallback.
    """
    import threading

    if devices[0].platform != "tpu":
        return
    box: dict = {}

    def probe() -> None:
        try:
            import jax
            import jax.numpy as jnp
            y = jax.jit(lambda x: x @ x)(jnp.ones((256, 256)))
            jax.block_until_ready(y)
            box["ok"] = True
        except BaseException as e:  # noqa: BLE001
            box["error"] = repr(e)

    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
    _log(f"probing device execution (watchdog {timeout:.0f}s) ...")
    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        _reexec_cpu(f"device execution probe hung for {timeout:.0f}s "
                    f"(relay outage?)")
    if "error" in box:
        # raise instead of falling back so _run_watched's one-retry policy
        # for transient relay faults applies before demoting to CPU
        raise RuntimeError(f"device execution probe failed: {box['error']}")
    _log("device executes ok")


# Committed artifact updated in place by every successful TPU run; the CPU
# fallback embeds its rows verbatim so BENCH_r*.json always carries real TPU
# numbers even through a relay outage (VERDICT r3 item 1).
_TPU_ROWS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_TPU_ROWS.json")

# Minimum measured steps for a row to enter the verified store; the
# flagship OOM ladder sizes its step count against this same bar.
_MIN_VERIFIED_STEPS = 10


def _stamp_round(row: dict) -> dict:
    """Ensure a verified row records the ROUND it was captured in
    (VERDICT weak #4): explicit ``round`` wins, else recovered from the
    legacy ``round<N>_chip_verified`` source tag."""
    if "round" not in row:
        m = re.search(r"round(\d+)", str(row.get("source", "")))
        if m:
            row = dict(row, round=int(m.group(1)))
    return row


def _null_nonchip_noise(row: dict, platform: str) -> dict:
    """CPU-fallback hygiene (VERDICT weak #4): ``vs_baseline``/``mfu`` are
    fractions of the TPU north-star target — computed from a CPU run they
    are noise that has been mistaken for signal in round reviews.  Null
    them on any non-TPU row; real timings (value, step_ms) stay."""
    if platform != "tpu":
        row = dict(row, vs_baseline=None, mfu=None)
    return row


def _load_verified_tpu_rows() -> list:
    try:
        with open(_TPU_ROWS_PATH) as f:
            rows = json.load(f)["rows"]
        return [_stamp_round(r) for r in rows if "value" in r]
    except (OSError, KeyError, ValueError, TypeError):
        # TypeError: valid JSON of the wrong shape (top-level list, row not
        # a dict) must fall back too — the fallback JSON line is guaranteed
        return [_stamp_round(r) for r in _LAST_VERIFIED_TPU_ROWS]


def _store_verified_tpu_rows(rows: list) -> None:
    """Merge newly measured TPU rows into the artifact by metric name.

    Merge, not replace: a custom single-config sweep or a budget-truncated
    matrix run measures a subset of the configs, and replacing wholesale
    would discard previously verified flagship/ViT rows from the fallback
    set."""
    tpu_rows = [r for r in rows if "value" in r and
                str(r.get("device", "")).lower().startswith("tpu")]
    # per-row gate: a low-step debug row must not overwrite a verified
    # headline number under the same metric key
    measured = [r for r in tpu_rows
                if r.get("steps", 0) >= _MIN_VERIFIED_STEPS]
    for r in tpu_rows:
        if r not in measured:
            _log(f"row {r['metric']} gated out of verified store "
                 f"(steps={r.get('steps')} < {_MIN_VERIFIED_STEPS})")
    if not measured:
        return
    merged = {r["metric"]: r for r in _load_verified_tpu_rows()}
    # the capture round rides along so the CPU fallback's embedded rows
    # always say WHEN they were really measured (BENCH_ROUND is stamped
    # by the driver; the date is the fallback provenance)
    stamp = {"source": f"chip_verified_{time.strftime('%Y-%m-%d')}"}
    if os.environ.get("BENCH_ROUND", "").isdigit():
        stamp["round"] = int(os.environ["BENCH_ROUND"])
    for r in measured:
        merged[r["metric"]] = dict(r, **stamp)
    try:
        # atomic replace: a crash mid-write must not truncate the artifact
        # (loader falls back to stale builtin rows on parse failure)
        tmp = _TPU_ROWS_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"note": "last chip-verified TPU bench rows "
                               "(auto-updated by a successful bench.py TPU "
                               "run; embedded by the CPU fallback)",
                       "rows": list(merged.values())}, f, indent=1)
        os.replace(tmp, _TPU_ROWS_PATH)
        _log(f"chip-verified rows stored -> {_TPU_ROWS_PATH}")
    except OSError as e:
        _log(f"could not store verified rows: {e!r}")


# Fallback of the fallback: rows as of the last run that edited this file.
_LAST_VERIFIED_TPU_ROWS = [
    {"metric": "train_throughput_efficientnet_b4_380x380x3_b64",
     "value": 3606.7, "unit": "frames/sec/chip", "mfu": 0.548,
     "device": "TPU v5 lite", "source": "round3_chip_verified"},
    {"metric": "train_throughput_efficientnet_b4_380x380x3_b16",
     "value": 390.0, "unit": "frames/sec/chip",
     "device": "TPU v5 lite", "source": "round3_chip_verified",
     "note": "dispatch-bound through the axon relay"},
    {"metric": "train_throughput_efficientnet_b4_380x380x3_b128",
     "value": 3624.0, "unit": "frames/sec/chip",
     "device": "TPU v5 lite", "source": "round3_chip_verified"},
]


def _run_config(devices, model_name: str, batch: int, size: int, chans: int,
                steps: int, dtype, extra=None, mode: str = "train") -> dict:
    """Measure one config (train step, or forward-only ``mode='infer'``);
    returns a result row."""
    import jax
    import numpy as np

    from deepfake_detection_tpu.losses import cross_entropy
    from deepfake_detection_tpu.models import create_model, init_model
    from deepfake_detection_tpu.optim import create_optimizer
    from deepfake_detection_tpu.train import (create_train_state,
                                              make_eval_step,
                                              make_train_step)

    tag = "/".join(f"{k}={v}" for k, v in (extra or {}).items())
    _log(f"config[{mode}]: {model_name} {size}x{size}x{chans} b{batch} "
         f"steps={steps} {tag} on {devices[0].device_kind}")
    _log("building + initializing model ...")
    import jax.numpy as jnp
    model = create_model(model_name, num_classes=2, in_chans=chans,
                         dtype=dtype if dtype != jnp.float32 else None,
                         **(extra or {}))
    variables = init_model(model, jax.random.PRNGKey(0),
                           (2, size, size, chans), training=True)
    cfg = SimpleNamespace(opt="rmsproptf", opt_eps=1e-8, momentum=0.9,
                          weight_decay=1e-5, lr=1.2e-5)
    # forward-only rows skip optimizer slots and the EMA duplicate (~3-4x
    # param memory a real deployment would not hold)
    import optax
    tx = create_optimizer(cfg) if mode != "infer" else optax.identity()
    state = create_train_state(variables, tx, with_ema=mode != "infer")
    # single chip → no mesh; plain jit path
    if mode == "infer":
        eval_step = make_eval_step(model, cross_entropy)

        def step(state, x, y, key):      # key ignored: deterministic eval
            return state, eval_step(state, x, y)
    else:
        step = make_train_step(model, tx, cross_entropy, mesh=None,
                               bn_mode="global", ema_decay=0.9998)

    # several distinct device-resident batches, cycled during measurement —
    # a single fixed batch gets memorized within ~2 steps (loss→0 in the
    # report) and lets XLA's scheduler see an unrealistically stable stream
    rng = np.random.default_rng(0)
    n_batches = 4
    xs = [jax.device_put(rng.normal(size=(batch, size, size, chans))
                         .astype(np.float32).astype(dtype))
          for _ in range(n_batches)]
    ys = [jax.device_put(rng.integers(0, 2, batch)) for _ in range(n_batches)]
    x, y = xs[0], ys[0]
    key = jax.random.PRNGKey(1)

    # FLOPs of the whole compiled step from XLA cost analysis
    _log(f"lowering + compiling {mode} step ...")
    lowered = jax.jit(step.__wrapped__ if hasattr(step, "__wrapped__")
                      else step).lower(state, x, y, key)
    compiled = lowered.compile()
    try:
        flops_per_step = float(compiled.cost_analysis()["flops"])
    except (KeyError, TypeError):
        flops_per_step = float("nan")
    _log(f"compiled; XLA cost analysis: {flops_per_step:.3e} flops/step")

    # warmup (also primes the donated-buffer path)
    _log("warmup (3 steps) ...")
    for i in range(3):
        state, metrics = step(state, x, y, jax.random.fold_in(key, i))
    jax.block_until_ready(metrics["loss"])

    _log(f"measuring ({steps} steps) ...")
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, xs[i % n_batches], ys[i % n_batches],
                              jax.random.fold_in(key, 100 + i))
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    frames_per_sec = batch * steps / dt
    peak = _peak_flops(devices[0])
    mfu = (flops_per_step * steps / dt) / peak if np.isfinite(
        flops_per_step) else float("nan")
    _log(f"done: {frames_per_sec:.1f} frames/s, "
         f"{dt / steps * 1000:.1f} ms/step, mfu={mfu:.3f}")
    name = f"{model_name}_{size}x{size}x{chans}_b{batch}"
    if extra and extra.get("attn_impl"):
        name += f"_{extra['attn_impl']}"
    row = {
        "metric": f"{'infer' if mode == 'infer' else 'train'}"
                  f"_throughput_{name}",
        "value": round(frames_per_sec, 2),
        "unit": "frames/sec/chip",
        "vs_baseline": round(mfu / 0.70, 4) if np.isfinite(mfu) else None,
        "mfu": round(mfu, 4) if np.isfinite(mfu) else None,
        "step_ms": round(dt / steps * 1000, 2),
        "steps": steps,
        "device": devices[0].device_kind,
        "loss": round(float(metrics["loss"]), 4),
    }
    if extra:
        row["config"] = dict(extra)
    return _null_nonchip_noise(row, devices[0].platform)


def _is_oom(err: BaseException) -> bool:
    return "resource_exhausted" in repr(err).lower() or \
        "out of memory" in repr(err).lower()


def main() -> None:
    devices = _init_backend()
    _probe_execution(devices)
    import jax.numpy as jnp

    on_tpu = devices[0].platform == "tpu"
    custom = any(os.environ.get(k) for k in
                 ("BENCH_MODEL", "BENCH_BATCH", "BENCH_SIZE", "BENCH_CHANS",
                  "BENCH_ATTN", "BENCH_REMAT"))
    rows = []

    if not on_tpu:
        # CPU fallback: one tiny config proves the path end-to-end; the
        # artifact's TPU story rides on the embedded verified rows
        row = _run_config(
            devices, os.environ.get("BENCH_MODEL", "efficientnet_b0"),
            int(os.environ.get("BENCH_BATCH", 2)),
            int(os.environ.get("BENCH_SIZE", 64)),
            int(os.environ.get("BENCH_CHANS", 3)),
            int(os.environ.get("BENCH_STEPS", 3)), jnp.float32)
        result = dict(row)
        result["note"] = (
            "CPU fallback (TPU relay unreachable at run time); "
            "'tpu_verified_rows' embeds the last chip-verified TPU row "
            "set verbatim")
        result["tpu_verified_rows"] = _load_verified_tpu_rows()
        print(json.dumps(result), flush=True)
        return

    steps = int(os.environ.get("BENCH_STEPS", 20))
    if custom:
        extra = {}
        if os.environ.get("BENCH_ATTN"):
            extra["attn_impl"] = os.environ["BENCH_ATTN"]
        if os.environ.get("BENCH_REMAT"):
            extra["remat_policy"] = os.environ["BENCH_REMAT"]
        rows.append(_run_config(
            devices, os.environ.get("BENCH_MODEL", "efficientnet_b4"),
            int(os.environ.get("BENCH_BATCH", 64)),
            int(os.environ.get("BENCH_SIZE", 380)),
            int(os.environ.get("BENCH_CHANS", 3)),
            steps, jnp.bfloat16, extra or None))
    else:
        # headline first — if the driver (or the relay) kills the matrix
        # midway, the budget check records what was skipped
        budget = float(os.environ.get("BENCH_MATRIX_BUDGET", 1200))
        # swept r3 on TPU v5e: b16→390 f/s (dispatch-bound), b64→3607 f/s
        # (0.55 MFU), b128→3624 f/s (flat) ⇒ 64 saturates the chip
        matrix = [("b4", lambda: _run_config(
            devices, "efficientnet_b4", 64, 380, 3, steps, jnp.bfloat16))]
        if os.environ.get("BENCH_MATRIX", "1") != "0":
            # flagship: OOM ladder over (batch, remat) — 600²×12 at B7
            # scale; the canonical cluster config is 3/GPU (train.sh:5)
            def flagship():
                for b, remat in ((8, "dots"), (4, "dots"), (2, "full")):
                    try:
                        # full-quality runs keep the flagship at enough
                        # measured steps to pass the per-row verified-store
                        # gate; debug runs stay short
                        fsteps = (max(_MIN_VERIFIED_STEPS, steps // 2)
                                  if steps >= _MIN_VERIFIED_STEPS
                                  else max(5, steps // 2))
                        return _run_config(
                            devices, "efficientnet_deepfake_v4", b, 600,
                            12, fsteps, jnp.bfloat16,
                            {"remat_policy": remat})
                    except BaseException as e:  # noqa: BLE001
                        if not _is_oom(e):
                            raise
                        _log(f"flagship b{b}/{remat} OOM; stepping down")
                raise RuntimeError("flagship OOM even at b2/full")

            matrix += [
                ("flagship_v4", flagship),
                ("vit_dense", lambda: _run_config(
                    devices, "vit_base_patch16_224", 128, 224, 3, steps,
                    jnp.bfloat16, {"attn_impl": "full"})),
                ("vit_flash", lambda: _run_config(
                    devices, "vit_base_patch16_224", 128, 224, 3, steps,
                    jnp.bfloat16, {"attn_impl": "flash"})),
                # deployment story: forward-only B4 (the reference serves
                # inference from the same backbone, test.py)
                ("b4_infer", lambda: _run_config(
                    devices, "efficientnet_b4", 128, 380, 3, steps,
                    jnp.bfloat16, mode="infer")),
                # the temporal extension flagship: divided space-time
                # attention over the 4-frame clips (models/timesformer.py);
                # last so a budget-truncated matrix never eats the
                # reference-parity rows above
                ("timesformer", lambda: _run_config(
                    devices, "timesformer_base_patch16_224", 32, 224, 12,
                    steps, jnp.bfloat16)),
            ]
        matrix_t0 = None
        for name, fn in matrix:
            if rows and matrix_t0 is None:
                matrix_t0 = time.perf_counter()   # budget excludes init +
                # the headline config (a slow relay day must not silently
                # eat the flagship/ViT rows)
            if matrix_t0 is not None and \
                    time.perf_counter() - matrix_t0 > budget:
                _log(f"matrix budget exceeded; skipping {name}")
                rows.append({"metric": name, "skipped":
                             f"matrix budget {budget:.0f}s exceeded"})
                continue
            try:
                rows.append(fn())
                # store INCREMENTALLY: if a later config hangs past the
                # watchdog (first chip contact after an outage is exactly
                # when that happens), the rows already measured survive
                # the CPU re-exec (custom sweeps take the other branch
                # above and never store)
                _store_verified_tpu_rows(rows[-1:])
            except BaseException as e:  # noqa: BLE001 — record, continue
                import traceback
                traceback.print_exc()
                _log(f"config {name} failed: {e!r}")
                rows.append({"metric": name, "error": repr(e)[:300]})

    headline = next((r for r in rows if "value" in r), rows[0])
    result = dict(headline)
    result["rows"] = rows
    print(json.dumps(result), flush=True)


# Error substrings treated as transient TPU-side faults worth one retry
# (round 2: the axon remote-compile proxy refused connections mid-init)
_TRANSIENT = ("connection refused", "remote_compile", "unavailable",
              "deadline exceeded", "socket closed", "connection reset")


def _is_transient(err: str) -> bool:
    low = err.lower()
    return any(s in low for s in _TRANSIENT)


def _retry_budget_left(timeout: float, elapsed: float,
                       floor: float = 60.0) -> bool:
    """BENCH_RUN_TIMEOUT is a GLOBAL budget; a transient-fault retry is
    only worth taking when at least ``floor`` seconds of it remain — a
    retry that would be watchdogged almost immediately just burns the CPU
    fallback's slice of an outer supervisor's stage allowance."""
    return timeout - elapsed >= floor


def _run_watched() -> None:
    """Run main() in a worker thread; watchdog + retry + CPU fallback."""
    import threading

    on_cpu = bool(os.environ.get("_BENCH_CPU_FALLBACK"))
    # 2400 s: the full matrix on a freshly recovered relay pays 5+ cold
    # compiles (~30-60 s each through the remote-compile proxy) plus the
    # flagship OOM ladder — a 900 s watchdog demoted exactly that
    # first-contact harvest to CPU
    timeout = float(os.environ.get("BENCH_RUN_TIMEOUT", 2400))
    attempts = 1 if on_cpu else 2
    t0 = time.perf_counter()
    for attempt in range(attempts):
        box: dict = {}

        def work() -> None:
            try:
                main()
                box["ok"] = True
            except BaseException as e:  # noqa: BLE001 — report, not die
                import traceback
                traceback.print_exc()
                box["error"] = repr(e)

        t = threading.Thread(target=work, daemon=True)
        t.start()
        # BENCH_RUN_TIMEOUT is a GLOBAL budget: a retry gets only the
        # remainder (never a fresh 60 s grant — _retry_budget_left gated
        # it), so watchdog + retry can never exceed an outer supervisor's
        # single-stage allowance
        remaining = timeout - (time.perf_counter() - t0)
        t.join(max(60.0, remaining) if attempt == 0 else max(0.0, remaining))
        if t.is_alive():
            # a hung jax call can't be interrupted — only exec/exit escapes
            if on_cpu:
                _fail_json("run", f"CPU run exceeded {timeout:.0f}s")
                os._exit(1)
            _reexec_cpu(f"run exceeded {timeout:.0f}s watchdog")
        if box.get("ok"):
            return
        err = box.get("error", "unknown")
        if attempt + 1 < attempts and _is_transient(err):
            if _retry_budget_left(timeout, time.perf_counter() - t0):
                _log(f"transient fault ({err[:200]}); retrying once ...")
                continue
            _log(f"transient fault ({err[:200]}) but <60s of "
                 "BENCH_RUN_TIMEOUT remains; skipping the retry")
        if on_cpu:
            _fail_json("run", err)
            os._exit(1)
        _reexec_cpu(f"run failed: {err[:200]}")


if __name__ == "__main__":
    try:
        _run_watched()
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — always emit a JSON line
        import traceback
        traceback.print_exc()
        _fail_json("run", repr(e))
        sys.exit(1)
